"""Learned cost model tests (ISSUE 16): corpus ingestion edge cases
(truncated JSONL line, missing attribution fields, duplicate
(run_id, step) dedup, non-object artifact — each CLASSIFIED, never a
crash), the mixed-vintage workload-key regression (pre-PR-13 JSONL
without ``|kb=`` joins under ``backend="unknown"``), the cost-model
file's tune-cache robustness contract (corrupt / truncated / schema
mismatch -> analytic defaults + ``tune.costmodel_errors``), fitting on
synthetic rows (holdout improvement, hbm_scale clamping), the
``PADDLE_TPU_COSTMODEL=0`` kill switch's bit-exactness, calibrated
static pruning (ordering preserved), and bench-history's
lower-is-better trajectory for ``gpt_attr_model_err_pct``."""

import json

import pytest

from paddle_tpu import tune
from paddle_tpu.observability import attribution as attr
from paddle_tpu.observability import bench_history
from paddle_tpu.observability import get_registry
from paddle_tpu.observability.corpus import Corpus, workload_field
from paddle_tpu.tune import costmodel as cm
from paddle_tpu.tune import space as tspace
from paddle_tpu.tune.costmodel_selftest import _TOY_HLO


@pytest.fixture
def tmp_model(tmp_path, monkeypatch):
    """Scope the tune cache (and therefore the cost-model file, which
    lives next to it) to a tmp dir; reset both singletons around."""
    monkeypatch.setenv("PADDLE_TPU_TUNE_CACHE", str(tmp_path / "tuned.json"))
    monkeypatch.delenv("PADDLE_TPU_COSTMODEL_PATH", raising=False)
    monkeypatch.delenv("PADDLE_TPU_COSTMODEL", raising=False)
    tune.reset_cache()
    cm.reset_model()
    yield tmp_path / "costmodel.json"
    tune.reset_cache()
    cm.reset_model()


def _plant(path, platform, entry):
    """Write a valid fitted model file with one platform entry and drop
    the singleton so the next consult loads it."""
    m = cm.CostModel(str(path))
    m.platforms = {platform: dict(entry)}
    m.version = 1
    m.save()
    cm.reset_model()
    return m


_ENTRY = {
    "total": [1.0, 2.0, 3.5],
    "classes": {"dot": [1.5, 0.5, 0.01], "pallas": [2.0, 0.0, 0.0]},
    "train_rows": 9, "holdout_rows": 3,
    "holdout_err_pct": 4.2, "analytic_err_pct": 88.0,
    "hbm_scale": 1.0,
}


# -- corpus ingestion edge cases (the satellite contract) -----------------

def _write_jsonl(path, lines):
    path.write_text("\n".join(lines) + "\n")
    return path


def test_trainer_jsonl_classifies_rot(tmp_path):
    """One good step ingests; a truncated line, a non-object line, a
    step without wall_time and a step without attribution fields each
    classify into ``skipped`` — never a crash."""
    p = _write_jsonl(tmp_path / "run.jsonl", [
        json.dumps({"event": "run_meta", "run_id": "rid1",
                    "git_sha": "abc123"}),
        json.dumps({"event": "step", "step": 1, "wall_time": 0.5,
                    "attr_workload": "op=step|t=128|kb=pallas_tpu",
                    "attr_est_ms": 3.0, "attr_model_err_pct": -99.4,
                    "attr_classes": {"dot": [1e9, 2e8, 3, 2.5]}}),
        '{"event": "step", "step":',                 # truncated write
        json.dumps([1, 2]),                          # not an object
        json.dumps({"event": "step", "step": 3}),    # no wall_time
        json.dumps({"event": "step", "step": 4, "wall_time": 0.3}),
        json.dumps({"event": "pass", "pass_id": 0}),  # expected, not rot
    ])
    co = Corpus()
    assert co.ingest_trainer_jsonl(p) == 1
    row = co.rows[0]
    assert row["run_id"] == "rid1" and row["git_sha"] == "abc123"
    assert row["measured_ms"] == 500.0
    assert row["backend"] == "pallas_tpu"
    assert row["classes"]["dot"]["est_ms"] == 2.5
    reasons = [r for _s, r in co.skipped]
    assert any("truncated or non-JSON line" in r for r in reasons)
    assert any("not a JSON object" in r for r in reasons)
    assert any("no measured wall_time" in r for r in reasons)
    assert any("no attribution fields" in r for r in reasons)
    assert len(co.skipped) == 4  # the pass record is NOT rot


def test_duplicate_run_id_step_rows_dedup(tmp_path):
    """Re-ingesting the same file is idempotent: every row classifies
    as a duplicate, the corpus does not grow."""
    p = _write_jsonl(tmp_path / "run.jsonl", [
        json.dumps({"event": "run_meta", "run_id": "rid1"}),
        json.dumps({"event": "step", "step": 1, "wall_time": 0.5,
                    "attr_workload": "op=step|t=128|kb=pallas_tpu",
                    "attr_est_ms": 3.0}),
        json.dumps({"event": "step", "step": 2, "wall_time": 0.4,
                    "attr_workload": "op=step|t=128|kb=pallas_tpu",
                    "attr_est_ms": 3.0}),
    ])
    co = Corpus()
    assert co.ingest_trainer_jsonl(p) == 2
    assert co.ingest_trainer_jsonl(p) == 0
    assert len(co) == 2
    assert sum("duplicate (run_id, step)" in r
               for _s, r in co.skipped) == 2


def test_nonobject_artifact_classified_not_crashed(tmp_path):
    """A valid-JSON-but-not-an-object artifact (torn write that still
    parses) classifies exactly like bench_history does."""
    p = tmp_path / "BENCH_r03.json"
    p.write_text("[1, 2, 3]")
    co = Corpus()
    assert co.ingest_artifact(p) == 0
    assert co.skipped == [
        ("BENCH_r03.json", "artifact is not a JSON object (list)")]


def test_artifact_ingest_reconstructs_measured(tmp_path):
    """A real-shaped bench artifact yields one corpus row with the
    measured wall reconstructed from the shipped est/err pair."""
    p = tmp_path / "BENCH_r07.json"
    p.write_text(json.dumps({"n": 7, "rc": 0, "parsed": {
        "metric": "gpt_tokens_per_sec_per_chip", "value": 100.0,
        "run_id": "artrun", "git_sha": "g1", "extra": {
            "gpt_attribution": {
                "workload": "op=step|t=128|kb=pallas_tpu",
                "est_ms_total": 2.5,
                "classes": {"dot": {"flops": 1e9, "bytes": 2e8,
                                    "ops": 3, "est_ms": 2.5}}},
            "gpt_attr_est_ms": 2.5,
            "gpt_attr_model_err_pct": -50.0}}}))
    co = Corpus()
    assert co.ingest_artifact(p) == 1
    row = co.rows[0]
    assert row["measured_ms"] == pytest.approx(5.0)  # 2.5 / (1 - 0.5)
    assert row["run_id"] == "artrun" and row["flops"] == 1e9
    # err_pct <= -100 is unreconstructable (division blows up): classify
    p2 = tmp_path / "BENCH_r08.json"
    p2.write_text(json.dumps({"n": 8, "rc": 0, "parsed": {
        "metric": "m", "value": 1.0, "extra": {
            "gpt_attribution": {"est_ms_total": 2.5},
            "gpt_attr_model_err_pct": -100.0}}}))
    assert co.ingest_artifact(p2) == 0
    assert any("no reconstructable measured time" in r
               for _s, r in co.skipped)


def test_corpus_save_load_roundtrip(tmp_path):
    co = Corpus()
    assert co.add_row("unit", workload="op=step|t=64|kb=xla_ref",
                      measured_ms=7.5, est_ms=1.0, flops=2e9,
                      run_id="r1", step=1)
    assert not co.add_row("unit", measured_ms=0.0, est_ms=1.0)  # gate
    store = tmp_path / "corpus.jsonl"
    co.save_jsonl(store)
    fresh = Corpus()
    assert fresh.load_jsonl(store) == 1
    assert fresh.rows[0]["workload"] == "op=step|t=64|kb=xla_ref"
    # loading AGAIN dedups (append-only store, idempotent read-back)
    assert fresh.load_jsonl(store) == 0
    assert len(fresh) == 1


# -- mixed-vintage JSONL: the pre-PR-13 |kb= regression -------------------

def test_normalize_workload_key_backfills_backend():
    assert attr.normalize_workload_key(
        "op=step|t=128") == "op=step|t=128|kb=unknown"
    assert attr.normalize_workload_key(
        "op=step|t=128|kb=pallas_tpu") == "op=step|t=128|kb=pallas_tpu"
    assert attr.normalize_workload_key(None) is None
    assert attr.normalize_workload_key("") is None
    assert attr.normalize_workload_key("freeform") == "freeform"


def test_mixed_vintage_jsonl_joins_under_unknown_backend(tmp_path):
    """The regression fix: a pre-PR-13 step record (workload key with
    no ``|kb=`` token) must INGEST — backend backfilled to "unknown" —
    instead of being silently skipped next to new-vintage rows."""
    p = _write_jsonl(tmp_path / "mixed.jsonl", [
        json.dumps({"event": "run_meta", "run_id": "old"}),
        json.dumps({"event": "step", "step": 1, "wall_time": 0.2,
                    "attr_workload": "op=step|t=128|b=4|plat=cpu",
                    "attr_est_ms": 1.5}),
        json.dumps({"event": "step", "step": 2, "wall_time": 0.2,
                    "attr_workload":
                        "op=step|t=128|b=4|plat=cpu|kb=pallas_tpu",
                    "attr_est_ms": 1.5}),
    ])
    co = Corpus()
    assert co.ingest_trainer_jsonl(p) == 2
    old, new = co.rows
    assert old["workload"].endswith("|kb=unknown")
    assert old["backend"] == "unknown" and old["platform"] == "cpu"
    assert new["backend"] == "pallas_tpu"
    assert co.summary()["backends"] == {"unknown": 1, "pallas_tpu": 1}


def test_reconcile_carries_normalized_workload():
    rec = attr.reconcile({"est_ms_total": 2.0,
                          "workload": "op=step|t=64"}, 0.004)
    assert rec["workload"] == "op=step|t=64|kb=unknown"
    assert rec["measured_ms"] == 4.0 and rec["err_pct"] == -50.0


def test_workload_field_parses_tokens():
    k = "op=flash|t=512|kb=pallas_tpu|plat=cpu"
    assert workload_field(k, "kb") == "pallas_tpu"
    assert workload_field(k, "plat") == "cpu"
    assert workload_field(k, "missing") is None
    assert workload_field(None, "kb") is None


# -- cost-model file robustness (tune-cache contract) ---------------------

def _errors():
    return get_registry().value("tune.costmodel_errors")


def test_costmodel_corrupt_file_degrades_to_analytic(tmp_model):
    plat = cm.current_platform()
    _plant(tmp_model, plat, _ENTRY)
    assert cm.active_entry(plat) is not None
    tmp_model.write_bytes(b"\x00garbage not json{{{")
    cm.reset_model()
    before = _errors()
    assert cm.active_entry(plat) is None
    m = cm.get_model()
    assert m.platforms == {} and "unreadable" in m.stale_reason
    assert _errors() == before + 1
    assert cm.model_status(plat) == {"mode": "analytic"}
    # the next fit rewrites a valid file over the garbage
    _plant(tmp_model, plat, _ENTRY)
    assert cm.active_entry(plat) is not None


def test_costmodel_truncated_file_degrades(tmp_model):
    plat = cm.current_platform()
    _plant(tmp_model, plat, _ENTRY)
    full = tmp_model.read_text()
    tmp_model.write_text(full[: len(full) // 2])
    cm.reset_model()
    before = _errors()
    assert cm.active_entry(plat) is None
    assert cm.get_model().stale_reason is not None
    assert _errors() == before + 1


def test_costmodel_schema_mismatch_degrades(tmp_model):
    plat = cm.current_platform()
    _plant(tmp_model, plat, _ENTRY)
    data = json.loads(tmp_model.read_text())
    data["schema_version"] = 999
    tmp_model.write_text(json.dumps(data))
    cm.reset_model()
    before = _errors()
    assert cm.active_entry(plat) is None
    assert "schema_version" in cm.get_model().stale_reason
    assert _errors() == before + 1


def test_costmodel_kill_switch_env(tmp_model, monkeypatch):
    plat = cm.current_platform()
    _plant(tmp_model, plat, _ENTRY)
    assert cm.model_status(plat)["mode"] == "fitted"
    monkeypatch.setenv("PADDLE_TPU_COSTMODEL", "0")
    assert cm.active_entry(plat) is None
    assert cm.model_status(plat) == {"mode": "analytic"}
    assert cm.hbm_scale_for(plat) == 1.0


# -- fitting on synthetic rows --------------------------------------------

def _linear_rows(n, platform="testplat"):
    """Rows drawn from measured = 2*gflops + 1*gbytes + 5ms overhead,
    with the analytic est_ms recorded ~100x low (the CPU story)."""
    rows = []
    for i in range(1, n + 1):
        gf, gb = float(i), 0.5 * i
        measured = 2.0 * gf + 1.0 * gb + 5.0
        rows.append({
            "platform": platform, "workload": f"op=step|t={i}|kb=unknown",
            "measured_ms": measured, "est_ms": measured / 100.0,
            "flops": gf * 1e9, "bytes": gb * 1e9,
            "classes": {"dot": {"flops": gf * 1e9, "bytes": gb * 1e9,
                                "ops": 2, "est_ms": measured / 100.0}},
            "run_id": f"r{i}", "step": i, "source": "unit",
        })
    return rows


def test_fit_improves_on_analytic_holdout():
    plats = cm.fit_cost_model(_linear_rows(12))
    e = plats["testplat"]
    assert e["train_rows"] == 9 and e["holdout_rows"] == 3
    assert e["holdout_err_pct"] is not None
    assert e["analytic_err_pct"] is not None
    # the recorded analytic estimate is ~100x low -> ~99% error; the
    # fitted linear model must beat it decisively on held-out rows
    assert e["holdout_err_pct"] < e["analytic_err_pct"]
    assert e["analytic_err_pct"] > 90.0
    assert e["holdout_err_pct"] < 25.0


def test_fit_too_few_rows_stays_analytic():
    assert cm.fit_cost_model(_linear_rows(2)) == {}


def test_hbm_scale_clamped_to_conservative_band():
    """Measured/estimated HBM ratios calibrate the bound but only
    within [1.0, 2.0] — the prune may tighten, never relax."""
    for ratio, expect in ((3.0, 2.0), (0.5, 1.0), (1.4, 1.4)):
        rows = _linear_rows(12)
        for r in rows:
            r["hbm_est_bytes"] = 1e9
            r["hbm_high_water_bytes"] = ratio * 1e9
        e = cm.fit_cost_model(rows)["testplat"]
        assert e["hbm_scale"] == pytest.approx(expect)
    assert cm.fit_cost_model(_linear_rows(12))["testplat"][
        "hbm_scale"] == 1.0  # no hbm pairs -> neutral


def test_fit_and_save_roundtrip(tmp_model):
    m = cm.fit_and_save(_linear_rows(12))
    assert m.version == 1 and tmp_model.exists()
    e = cm.get_model().entry("testplat")
    assert e is not None and len(e["total"]) == 3
    # refit bumps the version (cross-run lineage)
    assert cm.fit_and_save(_linear_rows(12)).version == 2


def test_predictions_from_planted_entry():
    ms, comp, mem = cm.predict_class_ms(_ENTRY, "dot", 2e9, 4e9, 10)
    assert comp == pytest.approx(3.0) and mem == pytest.approx(2.0)
    assert ms == pytest.approx(3.0 + 2.0 + 0.1)
    # unknown class falls back to the total's a/b with no overhead
    ms2, c2, m2 = cm.predict_class_ms(_ENTRY, "mystery", 1e9, 1e9, 5)
    assert ms2 == pytest.approx(1.0 + 2.0)
    # sched cost = pallas-class flops term + the per-step constant
    assert cm.predict_sched_ms(_ENTRY, 3e9) == pytest.approx(
        2.0 * 3.0 + 3.5)


# -- consult points: bit-exactness + ordering -----------------------------

def test_attribute_hlo_kill_switch_bit_exact(tmp_model, monkeypatch):
    """With a fitted model on disk, PADDLE_TPU_COSTMODEL=0 must
    reproduce the no-model attribution byte-for-byte."""
    baseline = attr.attribute_hlo(_TOY_HLO)  # no model file yet
    plat = cm.current_platform()
    _plant(tmp_model, plat, _ENTRY)
    fitted = attr.attribute_hlo(_TOY_HLO)
    assert json.dumps(fitted, sort_keys=True) != json.dumps(
        baseline, sort_keys=True)  # the fit is actually consulted
    monkeypatch.setenv("PADDLE_TPU_COSTMODEL", "0")
    killed = attr.attribute_hlo(_TOY_HLO)
    assert json.dumps(killed, sort_keys=True) == json.dumps(
        baseline, sort_keys=True)


def test_estimate_gpt_step_hbm_scale_and_kill_switch(tmp_model,
                                                     monkeypatch):
    args = dict(n_layer=6, d_model=768, n_head=12, vocab=32000,
                seq_len=16384, batch=6, policy="offload", accum=1)
    base = tspace.estimate_gpt_step_hbm(**args)
    plat = cm.current_platform()
    _plant(tmp_model, plat, dict(_ENTRY, hbm_scale=1.5))
    assert tspace.estimate_gpt_step_hbm(**args) == int(base * 1.5)
    monkeypatch.setenv("PADDLE_TPU_COSTMODEL", "0")
    assert tspace.estimate_gpt_step_hbm(**args) == base  # bit-exact


def test_prune_static_calibrated_ordering(tmp_model):
    """The calibrated slack test must preserve the analytic verdicts'
    structure: the best candidate always survives, analytic survivors
    stay survivors (overhead only LOOSENS the ratio), and a
    zero-overhead fit reproduces the analytic prune verbatim with the
    'calibrated roofline' reason."""
    cands = [{"block_q": bq, "block_k": bk}
             for bq, bk in ((128, 128), (256, 256), (512, 512))]
    # slack below the 256/512-block candidates' ~1.20x scheduled-flop
    # ratio so the analytic prune actually rejects something
    kw = dict(seq_len=512, d_head=64, n_head=4, roofline_slack=1.1)
    base_surv, base_pruned = tspace.prune_static(candidates=cands, **kw)
    assert base_surv and any("roofline" in r for _c, r in base_pruned)
    plat = cm.current_platform()
    # zero per-step overhead: fitted ratio == flop ratio exactly
    _plant(tmp_model, plat, dict(
        _ENTRY, total=[1.0, 2.0, 0.0],
        classes={"pallas": [2.0, 0.0, 0.0]}))
    surv0, pruned0 = tspace.prune_static(candidates=cands, **kw)
    assert [c["block_q"] for c in surv0] == [
        c["block_q"] for c in base_surv]
    assert any("calibrated roofline" in r for _c, r in pruned0)
    # a large per-step overhead dilutes flop deltas: every analytic
    # survivor still survives (never a NEW rejection) and the best
    # candidate is unchanged
    _plant(tmp_model, plat, dict(
        _ENTRY, total=[1.0, 2.0, 1e6],
        classes={"pallas": [2.0, 0.0, 0.0]}))
    surv_loose, _ = tspace.prune_static(candidates=cands, **kw)
    loose_keys = {(c["block_q"], c["block_k"]) for c in surv_loose}
    assert {(c["block_q"], c["block_k"])
            for c in base_surv} <= loose_keys
    assert base_surv[0]["block_q"] == surv_loose[0]["block_q"]


# -- bench-history: gpt_attr_model_err_pct is lower-is-better -------------

def _bench_artifact(dirp, rnd, err_pct):
    p = dirp / f"BENCH_r{rnd:02d}.json"
    p.write_text(json.dumps({"n": rnd, "rc": 0, "parsed": {
        # flag-exempt main metric, held constant: only the cost-model
        # error trajectory is under test here
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": 100.0, "unit": "img/s/chip",
        "extra": {"gpt_attr_model_err_pct": err_pct}}}))
    return p


def test_bench_history_flags_cost_model_drift(tmp_path):
    """|err| improving 50->40 never flags; worsening to 60 (+50% vs the
    best-so-far 40) flags with direction=lower_is_better; an
    artifact:metric ack green-lights exactly that regression."""
    _bench_artifact(tmp_path, 1, -50.0)  # signed: tracked as |err|
    _bench_artifact(tmp_path, 2, 40.0)
    _bench_artifact(tmp_path, 3, 60.0)
    summary, rows = bench_history.history(str(tmp_path))
    assert rows[0]["metrics"]["gpt_attr_model_err_pct"] == 50.0
    regs = [r for r in summary["regressions"]
            if r["metric"] == "gpt_attr_model_err_pct"]
    assert len(regs) == 1
    reg = regs[0]
    assert reg["artifact"] == "BENCH_r03.json" and reg["value"] == 60.0
    assert reg["best"] == 40.0 and reg["direction"] == "lower_is_better"
    assert not summary["ok"]
    acked, _ = bench_history.history(str(tmp_path), known_failures={
        "BENCH_r03.json:gpt_attr_model_err_pct": "known CPU-noise round"})
    assert acked["ok"] and acked["acknowledged"] == [
        "BENCH_r03.json:gpt_attr_model_err_pct"]


def test_bench_history_improving_error_never_flags(tmp_path):
    for rnd, err in ((1, 80.0), (2, 50.0), (3, 45.0)):
        _bench_artifact(tmp_path, rnd, err)
    summary, _rows = bench_history.history(str(tmp_path))
    assert summary["ok"] and not summary["regressions"]
    assert "gpt_attr_model_err_pct" in summary["metrics_tracked"]
