"""Whole-model gradient check (--job=checkgrad; reference
TrainerMain.cpp:54 -> Trainer.cpp:303 checkGradient): finite differences
through the complete jitted step vs the analytic jax.grad backward."""

import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_checkgrad_lenet_passes():
    from paddle_tpu.models import lenet

    outs = lenet.build(learning_rate=0.01)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(0)
    feed = {
        "img": rng.normal(size=(4, 1, 28, 28)).astype(np.float32),
        "label": rng.integers(0, 10, (4, 1)).astype(np.int64),
    }
    ok, report = pt.check_gradients(feed, outs["avg_cost"],
                                    max_elements_per_param=4)
    assert ok, report
    assert len(report) >= 4  # conv + fc weights and biases
    for n, r in report.items():
        assert r["max_rel_err"] <= 3e-2, (n, r)


def test_checkgrad_does_not_mutate_state():
    """The check must never run optimizer ops or advance the RNG."""
    x = layers.data("x", shape=[4])
    y = layers.data("y", shape=[1])
    h = layers.fc(input=x, size=8, act="tanh")
    h = layers.dropout(h, 0.3)  # rng-consuming op: masks must be pinned
    loss = layers.mean(layers.square_error_cost(
        layers.fc(input=h, size=1), y))
    pt.optimizer.Adam(learning_rate=0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    scope = pt.core.scope.global_scope()
    rng = np.random.default_rng(1)
    feed = {"x": rng.normal(size=(8, 4)).astype(np.float32),
            "y": rng.normal(size=(8, 1)).astype(np.float32)}
    before = {n: np.asarray(scope.get(n)).copy()
              for n in scope.var_names()}
    ok, _ = pt.check_gradients(feed, loss)
    assert ok
    for n, v in before.items():
        np.testing.assert_array_equal(np.asarray(scope.get(n)), v,
                                      err_msg=n)


def test_checkgrad_catches_wrong_vjp():
    """Negative control: an op whose backward is deliberately wrong must
    FAIL the whole-model check (this is the regression the mode exists
    to catch)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core import registry

    impl = registry.get_op_impl("tanh")
    orig_fn = impl.fn

    @jax.custom_vjp
    def bad_tanh(x):
        return jnp.tanh(x)

    def bad_fwd(x):
        return jnp.tanh(x), x

    def bad_bwd(x, g):
        return (g * 0.37,)  # wrong derivative

    bad_tanh.defvjp(bad_fwd, bad_bwd)

    def bad_impl(X, **_):
        return {"Out": bad_tanh(X)}

    impl.fn = bad_impl
    try:
        x = layers.data("x", shape=[3])
        y = layers.data("y", shape=[1])
        h = layers.fc(input=x, size=6, act="tanh")
        loss = layers.mean(layers.square_error_cost(
            layers.fc(input=h, size=1), y))
        pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        rng = np.random.default_rng(2)
        feed = {"x": rng.normal(size=(8, 3)).astype(np.float32),
                "y": rng.normal(size=(8, 1)).astype(np.float32)}
        ok, report = pt.check_gradients(feed, loss,
                                        max_elements_per_param=6)
        assert not ok, report
    finally:
        impl.fn = orig_fn


def test_checkgrad_cli(tmp_path):
    """`python -m paddle_tpu train --job=checkgrad` — the TrainerMain
    --job flag surface."""
    cfg = tmp_path / "cfg.py"
    cfg.write_text(
        "import numpy as np\n"
        "import paddle_tpu as pt\n"
        "from paddle_tpu import layers\n"
        "def build():\n"
        "    x = layers.data('x', shape=[4])\n"
        "    y = layers.data('y', shape=[1])\n"
        "    pred = layers.fc(input=x, size=1)\n"
        "    loss = layers.mean(layers.square_error_cost(pred, y))\n"
        "    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)\n"
        "    return {'feed': [x, y], 'avg_cost': loss}\n"
        "def train_reader():\n"
        "    rng = np.random.default_rng(0)\n"
        "    for _ in range(8):\n"
        "        x = rng.normal(size=(4,)).astype(np.float32)\n"
        "        yield x, x.sum(keepdims=True)\n"
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PADDLE_TPU_PLATFORM"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu", "train", "--job", "checkgrad",
         str(cfg), "--batch-size", "4"],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "checkgrad PASSED" in r.stdout, r.stdout


def test_checkgrad_respects_no_grad_set():
    """Params excluded from backward (no @GRAD var) are skipped by
    default and rejected loudly when requested explicitly."""
    x = layers.data("x", shape=[4])
    y = layers.data("y", shape=[1])
    h = layers.fc(input=x, size=6, act="tanh", name="frozen")
    loss = layers.mean(layers.square_error_cost(
        layers.fc(input=h, size=1, name="head"), y))
    pt.optimizer.SGD(learning_rate=0.1).minimize(
        loss, no_grad_set={"frozen.w", "frozen.b"})
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.default_rng(3)
    feed = {"x": rng.normal(size=(8, 4)).astype(np.float32),
            "y": rng.normal(size=(8, 1)).astype(np.float32)}
    ok, report = pt.check_gradients(feed, loss)
    assert ok
    assert "frozen.w" not in report and "head.w" in report
    with pytest.raises(ValueError, match="excluded from backward"):
        pt.check_gradients(feed, loss, params=["frozen.w"])
