"""Speculative decoding on the paged serving engine
(paddle_tpu/serving/speculative.py) — token-exact parity vs plain
greedy decode, geometry validation at construction, kill switch,
zero scratch-block leak, mid-verify slot death, and the tuned
``op=spec_decode`` draft window.  All on the CPU mesh (conftest),
tiny model shapes."""

import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models import transformer
from paddle_tpu.observability import metrics as _obs
from paddle_tpu.serving import ServingEngine
from paddle_tpu.serving import speculative as spec


def _make_params(vocab=50, n_layer=2, n_head=2, d_model=32, max_len=48,
                 dtype="float32", seed=7):
    """Randomly initialized flagship weights (greedy chains over random
    weights are deterministic — spec parity doesn't need training)."""
    pt.core.unique_name.reset()
    main, startup = pt.Program(), pt.Program()
    main.random_seed = seed
    with pt.program_guard(main, startup):
        transformer.build(vocab_size=vocab, n_layer=n_layer,
                          n_head=n_head, d_model=d_model, max_len=max_len,
                          dropout_rate=0.0, dtype=dtype)
    exe = pt.Executor()
    exe.run(startup)
    return transformer.extract_params(program=main)


VOCAB, NL, NH, DM, T = 50, 2, 2, 32, 48


@pytest.fixture
def params():
    return _make_params(VOCAB, NL, NH, DM, T)


@pytest.fixture(autouse=True)
def fresh_serving_metrics():
    _obs.get_registry().clear(prefix="serving.")
    yield


def _engine(params, **kw):
    kw.setdefault("max_len", T)
    kw.setdefault("max_slots", 4)
    kw.setdefault("decode_chunk", 4)
    kw.setdefault("min_bucket", 4)
    return ServingEngine(params, NL, NH, DM, **kw)


def _refs(params, prompts, max_new):
    outs = []
    for p in prompts:
        toks, _ = transformer.generate(params, np.asarray(p)[None],
                                       max_len=T, n_layer=NL, n_head=NH,
                                       d_model=DM, return_logits=False)
        outs.append(np.asarray(toks)[0][: len(p) + max_new])
    return outs


def _prompts(rng, n, lens=(3, 7, 5, 9, 4, 11)):
    return [rng.integers(1, VOCAB, (lens[i % len(lens)],)).astype(np.int32)
            for i in range(n)]


# -- token-exact parity ------------------------------------------------------

@pytest.mark.parametrize("reuse", [True, False])
def test_spec_parity_token_exact_f32(params, reuse):
    """The acceptance bar: a speculative engine (depth-pruned draft)
    emits EXACTLY the tokens of plain greedy decode — mixed lengths,
    slot reuse, block-boundary crossings and all — and actually ran
    speculative rounds (proposed > 0)."""
    rng = np.random.default_rng(11)
    prompts = _prompts(rng, 6)
    eng = _engine(params, prefix_reuse=reuse,
                  draft_params=spec.depth_draft(params, 1), spec_k=3)
    assert eng._spec is not None and eng.spec_k == 3
    outs = eng.generate_many(prompts, max_new_tokens=10)
    for o, ref in zip(outs, _refs(params, prompts, 10)):
        np.testing.assert_array_equal(o, ref)
    assert eng._spec.proposed > 0
    # propose/verify/accept actually happened and is observable
    st = eng.stats()
    assert st["serving.spec_compiles"] >= 2  # draft chunk + verify
    assert 0.0 <= st["serving.spec_accept_rate"] <= 1.0


def test_spec_parity_bf16_bit_exact(params):
    """bf16 weights: speculative output is bit-identical to the plain
    bf16 engine (parity is exactness of the SCHEDULE, not a numeric
    tolerance — both paths run the same bf16 kernels)."""
    import jax.numpy as jnp

    p16 = {k: (jnp.asarray(v, jnp.bfloat16)
               if (k.startswith("block") or k.startswith("lm_head"))
               and k.endswith(".w") else v)
           for k, v in params.items()}
    rng = np.random.default_rng(12)
    prompts = _prompts(rng, 4)
    plain = _engine(p16).generate_many(prompts, max_new_tokens=8)
    _obs.get_registry().clear(prefix="serving.")
    eng = _engine(p16, draft_params=spec.depth_draft(p16, 1), spec_k=3)
    for o, ref in zip(eng.generate_many(prompts, max_new_tokens=8), plain):
        np.testing.assert_array_equal(o, ref)


def test_adversarial_draft_stays_exact(params):
    """A draft with UNRELATED weights (different init seed): acceptance
    collapses but every committed token is still exact — the guarantee
    is unconditional on draft quality, rejection just costs rollback."""
    adv = _make_params(VOCAB, NL, NH, DM, T, seed=1234)
    rng = np.random.default_rng(13)
    prompts = _prompts(rng, 5)
    # small blocks so rejected proposals cross block boundaries and the
    # rollback path (not just pointer rewind inside one block) runs
    eng = _engine(params, block_tokens=4,
                  draft_params=spec.depth_draft(adv, 1), spec_k=4)
    outs = eng.generate_many(prompts, max_new_tokens=12)
    for o, ref in zip(outs, _refs(params, prompts, 12)):
        np.testing.assert_array_equal(o, ref)
    sp = eng._spec
    assert sp.proposed > 0
    assert sp.accepted / sp.proposed < 0.5  # the draft really is bad
    assert eng.stats()["serving.spec_rollback_blocks"] > 0


# -- construction-time geometry validation -----------------------------------

def test_geometry_mismatches_rejected(params):
    """Every draft/target geometry mismatch fails LOUDLY at engine
    construction with a message naming the mismatch — never as garbage
    tokens at serve time."""
    other_vocab = _make_params(vocab=60)
    with pytest.raises(ValueError, match="vocab mismatch"):
        _engine(params, draft_params=other_vocab)

    other_width = _make_params(d_model=64, n_head=2)
    with pytest.raises(ValueError, match="d_model"):
        _engine(params, draft_params=other_width)

    # differing head count (even at equal d_model) changes the pool
    # block shape the draft would write into
    with pytest.raises(ValueError, match="n_head"):
        _engine(params, draft_params=spec.depth_draft(params, 1),
                draft_n_head=1)

    # depth bounds: zero layers, more layers than the dict carries,
    # deeper than the target (the draft rides the FIRST pool arrays)
    draft = spec.depth_draft(params, 1)
    with pytest.raises(ValueError, match="outside"):
        _engine(params, draft_params=draft, draft_n_layer=0)
    with pytest.raises(ValueError, match="outside"):
        _engine(params, draft_params=draft, draft_n_layer=2)
    deep = _make_params(n_layer=3, max_len=T)
    with pytest.raises(ValueError, match="cannot be deeper"):
        _engine(params, draft_params=deep)

    # a draft whose position table is shorter than max_len would index
    # out of bounds mid-serve
    short = _make_params(max_len=16)
    with pytest.raises(ValueError, match="position-embedding"):
        _engine(params, draft_params=short)

    with pytest.raises(ValueError, match="spec_k"):
        _engine(params, draft_params=draft, spec_k=0)


def test_depth_draft_helper_bounds(params):
    assert spec.draft_depth(params) == NL
    assert spec.draft_depth(spec.depth_draft(params, 1)) == 1
    with pytest.raises(ValueError, match="outside"):
        spec.depth_draft(params, 0)
    with pytest.raises(ValueError, match="outside"):
        spec.depth_draft(params, NL + 1)


# -- kill switch -------------------------------------------------------------

def test_kill_switch_is_bit_exact_plain_engine(params):
    """PADDLE_TPU_SPEC=0: draft_params is ignored wholesale — no spec
    state, no spec metrics, and output bit-identical to an engine built
    with no draft at all."""
    rng = np.random.default_rng(14)
    prompts = _prompts(rng, 4)
    plain = _engine(params).generate_many(prompts, max_new_tokens=8)
    os.environ["PADDLE_TPU_SPEC"] = "0"
    try:
        _obs.get_registry().clear(prefix="serving.")
        eng = _engine(params, draft_params=spec.depth_draft(params, 1),
                      spec_k=3)
        assert eng._spec is None and eng.spec_k is None
        outs = eng.generate_many(prompts, max_new_tokens=8)
    finally:
        os.environ.pop("PADDLE_TPU_SPEC", None)
    for o, ref in zip(outs, plain):
        np.testing.assert_array_equal(o, ref)
    assert not any(k.startswith("serving.spec_") for k in eng.stats())


# -- zero-leak discipline ----------------------------------------------------

@pytest.mark.parametrize("reuse", [True, False])
def test_scratch_blocks_never_leak(params, reuse):
    """After run_until_idle every scratch chain is released: pool
    accounting matches a plain engine's endpoint (cached prefix chains
    only with reuse on; zero without), scratch table zeroed."""
    rng = np.random.default_rng(15)
    prompts = _prompts(rng, 6)
    plain = _engine(params, prefix_reuse=reuse)
    plain.generate_many(prompts, max_new_tokens=8)
    base_in_use = plain.kv_pool.blocks_in_use

    _obs.get_registry().clear(prefix="serving.")
    eng = _engine(params, prefix_reuse=reuse,
                  draft_params=spec.depth_draft(params, 1), spec_k=3)
    eng.generate_many(prompts, max_new_tokens=8)
    sp = eng._spec
    assert eng.kv_pool.blocks_in_use == base_in_use
    if not reuse:
        assert eng.kv_pool.blocks_in_use == 0
    assert all(not (c or ()) for c in sp.chains)
    assert not np.count_nonzero(sp.table)
    assert (eng._table == 0).all()


# -- fault injection: slot death mid-verify ----------------------------------

def test_slot_death_mid_verify_reclaims_scratch_and_real_chains(params):
    """PADDLE_TPU_FAULT=slot_death:n fires at the decode point — in
    speculative mode that is MID-VERIFY, with the victim holding both a
    real chain and a draft scratch chain.  Both are reclaimed (pool
    back to baseline, both tables zeroed), survivors stay token-exact,
    and the driver keeps serving."""
    from paddle_tpu.resilience import faults

    eng = _engine(params, max_slots=3, prefix_reuse=False,
                  block_tokens=4,
                  draft_params=spec.depth_draft(params, 1), spec_k=3)
    rng = np.random.default_rng(16)
    baseline_in_use = eng.kv_pool.blocks_in_use
    os.environ["PADDLE_TPU_FAULT"] = "slot_death:2"
    faults.reset()
    eng.start()
    try:
        reqs = [eng.submit(rng.integers(1, VOCAB, (5,)),
                           max_new_tokens=10) for _ in range(6)]
        for r in reqs:
            assert r.wait(timeout=120), "request did not finish"
    finally:
        eng.stop()
        os.environ.pop("PADDLE_TPU_FAULT", None)
        faults.reset()
    dead = [r for r in reqs if r.error is not None]
    ok = [r for r in reqs if r.error is None]
    assert len(dead) == 1 and len(ok) == 5
    for r in ok:
        ref, _ = transformer.generate(params, r.prompt[None], max_len=T,
                                      n_layer=NL, n_head=NH, d_model=DM,
                                      return_logits=False)
        np.testing.assert_array_equal(
            r.result(timeout=0),
            np.asarray(ref)[0][: len(r.prompt) + 10])
    # neither the real chains nor the draft scratch chains leak
    assert eng.kv_pool.blocks_in_use == baseline_in_use == 0
    assert (eng._table == 0).all()
    assert not np.count_nonzero(eng._spec.table)
    assert all(not (c or ()) for c in eng._spec.chains)
    st = eng.stats()
    assert st["serving.slot_deaths"] == 1
    assert st["serving.completed"] == 5
    assert eng.idle


# -- tuned draft window (op=spec_decode, docs/autotune.md) -------------------

def test_engine_consults_tuned_spec_window(params, tmp_path, monkeypatch):
    """docs/autotune.md "Adding a tunable op": a measured
    tune_spec_decode search persists {k} under op=spec_decode, an
    engine constructed with a draft but NO explicit spec_k picks the
    winner up; explicit spec_k still wins; the kill switch keeps the
    hand-picked default; and in cached mode a miss NEVER builds an
    engine (no measurement on the serving path)."""
    from paddle_tpu import tune

    draft = spec.depth_draft(params, 1)
    monkeypatch.setenv("PADDLE_TPU_TUNE_CACHE",
                       str(tmp_path / "tuned.json"))
    monkeypatch.setenv("PADDLE_TPU_TUNE", "cached")
    tune.reset_cache()
    try:
        # cached-mode miss: no engine built, no candidates measured
        miss = tune.tune_spec_decode(params, draft, NL, NH, DM,
                                     max_len=T)
        assert miss["source"] == "miss" and miss["entry"] is None
        assert miss["measured"] == []

        monkeypatch.setenv("PADDLE_TPU_TUNE", "search")
        report = tune.tune_spec_decode(
            params, draft, NL, NH, DM, max_len=T, max_slots=2,
            requests=2, prompt_len=4, max_new=4, ks=(2, 3),
            max_measure=2)
        assert report["source"] == "search"
        win = report["entry"]["config"]
        assert set(win) == {"k"} and win["k"] in (2, 3)

        # draft-but-no-spec_k engine resolves the tuned winner
        monkeypatch.setenv("PADDLE_TPU_TUNE", "cached")
        eng = _engine(params, draft_params=draft)
        assert eng.spec_k == win["k"]

        # a second lookup is a cache hit, not a re-search
        again = tune.tune_spec_decode(params, draft, NL, NH, DM,
                                      max_len=T)
        assert again["source"] == "cache"

        # explicit spec_k always wins
        eng2 = _engine(params, draft_params=draft, spec_k=2)
        assert eng2.spec_k == 2

        # kill switch: hand-picked default, no lookup at all
        monkeypatch.setenv("PADDLE_TPU_TUNE", "off")
        eng3 = _engine(params, draft_params=draft)
        assert eng3.spec_k == spec.DEFAULT_SPEC_K
    finally:
        tune.reset_cache()
