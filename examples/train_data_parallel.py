"""Data-parallel ResNet over a device mesh — one annotation replaces the
reference's MultiGradientMachine/parallel_do/NCCL stack.  Optimizer
state (the Momentum velocities here) shards automatically over the dp
axis — ZeRO-1, docs/parallel.md; ``PADDLE_TPU_ZERO=0`` replicates.

Runs on real chips, or on a virtual mesh:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/train_data_parallel.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def build_program():
    """The example's training program (with the data-parallel batch
    annotations but no mesh/devices), built without running — the entry
    point ``python -m paddle_tpu --lint-selftest`` lints.  Returns
    (main_program, startup_program, fetch_list)."""
    import paddle_tpu as pt
    from paddle_tpu import parallel

    main_prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_prog, startup):
        model = pt.models.resnet.build(depth=20, class_dim=10,
                                       image_shape=(3, 32, 32),
                                       learning_rate=0.05,
                                       dtype="float32")
    parallel.data_parallel(main_prog, "dp", programs=(startup,))
    return main_prog, startup, [model["avg_cost"], model["accuracy"]]


def main():
    import jax

    import paddle_tpu as pt
    from paddle_tpu import parallel

    n = len(jax.devices())
    mesh = parallel.make_mesh({"dp": n})
    print(f"mesh: {n} devices on axis 'dp'")

    model = pt.models.resnet.build(depth=20, class_dim=10,
                                   image_shape=(3, 32, 32),
                                   learning_rate=0.05, dtype="float32")
    parallel.data_parallel(pt.default_main_program(), "dp",
                           programs=(pt.default_startup_program(),))

    exe = pt.Executor(mesh=mesh)
    exe.run(pt.default_startup_program())

    rep = parallel.optimizer_state_report(pt.default_main_program(), mesh)
    print(f"optimizer state: {rep['total_bytes'] / 1e6:.2f} MB total, "
          f"{rep['per_device_bytes'] / 1e6:.2f} MB/device "
          f"({rep['sharded_vars']} ZeRO-sharded vars)")

    rng = np.random.default_rng(0)
    batch = 8 * n  # global batch; shards across dp automatically
    for step in range(10):
        img = rng.normal(size=(batch, 3, 32, 32)).astype(np.float32)
        lbl = rng.integers(0, 10, (batch, 1)).astype(np.int64)
        cost, acc = exe.run(feed={"img": img, "label": lbl},
                            fetch_list=[model["avg_cost"],
                                        model["accuracy"]])
        print(f"step {step} cost {float(np.asarray(cost).ravel()[0]):.4f} "
              f"acc {float(np.asarray(acc).ravel()[0]):.3f}")


if __name__ == "__main__":
    main()
