"""MNIST LeNet end to end: model zoo + Trainer events + async checkpoints
+ export + reload (the reference book chapter 2 workflow).

    python examples/train_mnist.py [--passes 3]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as pt


def build_program():
    """The example's training program, built without running — the
    entry point ``python -m paddle_tpu --lint-selftest`` lints.
    Returns (main_program, startup_program, fetch_list)."""
    main_prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_prog, startup):
        model = pt.models.lenet.build(learning_rate=0.001)
    return main_prog, startup, [model["avg_cost"], model["accuracy"]]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--passes", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--out", default="mnist_model")
    ap.add_argument("--run-log", default=None,
                    help="write per-step telemetry (wall time, throughput, "
                         "MFU, compile counts) to this JSONL file")
    args = ap.parse_args()

    model = pt.models.lenet.build(learning_rate=0.001)
    feeder = pt.DataFeeder(model["feed"])

    def train_reader():
        for img, lbl in pt.dataset.mnist.train()():
            yield img.reshape(1, 28, 28), lbl

    def handler(e):
        if isinstance(e, pt.trainer.EndIteration) and e.batch_id % 50 == 0:
            acc = float(np.asarray(e.metrics[0]).ravel()[0])
            print(f"pass {e.pass_id} batch {e.batch_id} "
                  f"cost {e.cost:.4f} acc {acc:.3f}")

    tr = pt.trainer.Trainer(model["avg_cost"], model["feed"],
                            extra_fetch=[model["accuracy"]])
    # telemetry rides along with the user handler: step summaries every
    # 50 batches + (optionally) a JSONL run log for offline analysis
    reporter = pt.observability.MetricsReporter(
        log_every_n=50, jsonl_path=args.run_log)
    try:
        tr.train(pt.reader.batch(train_reader, args.batch_size),
                 num_passes=args.passes,
                 event_handler=reporter.chain(handler),
                 checkpoint_dir="mnist_ckpts", async_checkpoint=True)
    finally:
        reporter.close()

    pt.io.save_inference_model(args.out, ["img"], [model["prediction"]],
                               tr.exe)
    engine = pt.inference.InferenceEngine(args.out)
    sample = list(pt.reader.firstn(train_reader, 4)())
    probs = engine.run(feed={"img": np.stack([im for im, _ in sample])})
    pred = np.asarray(probs[0]).argmax(axis=1)
    print("reloaded model predictions:", pred.tolist(),
          "labels:", [int(l) for _, l in sample])


if __name__ == "__main__":
    main()
