"""Train a small GPT on synthetic data and decode with the KV cache —
the long-context flagship in ~40 lines.

    python examples/train_transformer.py [--steps 200]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as pt
from paddle_tpu.models import transformer


def build_program(vocab=64, seq=64):
    """The example's training program, built without running — the
    entry point ``python -m paddle_tpu --lint-selftest`` lints.
    Returns (main_program, startup_program, fetch_list)."""
    main_prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_prog, startup):
        outs = transformer.build(vocab_size=vocab, n_layer=2, n_head=4,
                                 d_model=128, max_len=seq,
                                 dropout_rate=0.0, learning_rate=3e-3,
                                 dtype="float32")
    return main_prog, startup, [outs["avg_cost"]]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--seq", type=int, default=64)
    args = ap.parse_args()

    outs = transformer.build(vocab_size=args.vocab, n_layer=2, n_head=4,
                             d_model=128, max_len=args.seq,
                             dropout_rate=0.0, learning_rate=3e-3,
                             dtype="float32")
    exe = pt.Executor()
    exe.run(pt.default_startup_program())

    rng = np.random.default_rng(0)
    for step in range(args.steps):
        toks = rng.integers(0, args.vocab, (16, args.seq)).astype(np.int64)
        lbls = (toks + 1) % args.vocab  # learn "next token = tok + 1"
        (cost,) = exe.run(feed={"tokens": toks, "labels": lbls},
                          fetch_list=[outs["avg_cost"]])
        if step % 50 == 0:
            print(f"step {step} loss {float(np.asarray(cost).ravel()[0]):.4f}")

    params = transformer.extract_params()
    prompt = np.asarray([[5, 6, 7]], np.int64)
    tokens, _ = transformer.generate(params, prompt, max_len=16,
                                     n_layer=2, n_head=4, d_model=128)
    print("prompt [5, 6, 7] ->", np.asarray(tokens)[0].tolist())


if __name__ == "__main__":
    main()
