#!/usr/bin/env bash
# Tier-1 verify with a DOTS_PASSED regression gate.
#
# Runs the ROADMAP.md tier-1 pytest command, counts passed tests the same
# way the driver does (dots in the progress lines), and fails if the count
# drops below the floor recorded in tests/TIER1_FLOOR.  Raise the floor
# whenever a PR adds passing tests; never lower it.
#
# Usage: tools/tier1.sh
set -o pipefail
cd "$(dirname "$0")/.."
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
    -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
passed=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
floor=$(cat tests/TIER1_FLOOR 2>/dev/null || echo 0)
echo "DOTS_PASSED=$passed (floor: $floor)"
if [ "$passed" -lt "$floor" ]; then
    echo "TIER1 REGRESSION: DOTS_PASSED $passed < floor $floor" >&2
    exit 1
fi
# the metrics-selftest smoke entry rides along: the telemetry subsystem
# must stay healthy for every perf PR that reads it
if ! python -m paddle_tpu --metrics-selftest > /tmp/_t1_selftest.log 2>&1; then
    echo "TIER1 REGRESSION: metrics selftest failed" >&2
    cat /tmp/_t1_selftest.log >&2
    exit 1
fi
# backward-pass memory smoke: the no-accelerator scan-locality /
# memory_analysis regression (docs/memory.md invariants) run explicitly —
# all four memory_optimize policies must keep their flash kernel calls
# scan-local and offload must stay bit-exact vs selective
if ! timeout -k 10 600 env JAX_PLATFORMS=cpu \
        python -m paddle_tpu --memory-selftest \
        > /tmp/_t1_memtest.log 2>&1; then
    echo "TIER1 REGRESSION: memory selftest failed" >&2
    cat /tmp/_t1_memtest.log >&2
    exit 1
fi
# multichip smoke: the scaling-engine invariants on the 8-device virtual
# CPU mesh — ZeRO-1 accumulator sharding (state bytes/device <=
# replicated/4), one cross-chip gradient reduction per optimizer step
# under accum (comm audit on compiled HLO), ZeRO/FSDP bit-exactness vs
# the replicated spelling, and the true-ZeRO-3 gradient gates:
# zero3_grad_contract clean on the compiled plan (one boundary
# reduce-scatter@fsdp per fsdp-tagged grad, zero in-loop reduces),
# prologue (embedding + LM head) bytes/device bound, 5-step
# bit-exactness vs PADDLE_TPU_ZERO3_RS=0, and comm_diff naming the
# moved collectives (docs/parallel.md rule 4)
if ! timeout -k 10 600 env JAX_PLATFORMS=cpu \
        python -m paddle_tpu --multichip-selftest \
        > /tmp/_t1_multichip.log 2>&1; then
    echo "TIER1 REGRESSION: multichip selftest failed" >&2
    cat /tmp/_t1_multichip.log >&2
    exit 1
fi
# static-analysis smoke: the lint pass framework's planted-defect /
# clean-program contract — every seeded check fires on its deliberately
# broken Program (dead code, shape-dtype, read-before-write, fetch
# overwrite, bf16 accum, tanh-in-scan, scan-locality, degraded offload,
# HBM preflight, donation audit, in-loop collective on the 2-device
# virtual mesh), the GPT benchmark program lints to ZERO findings, and
# every examples/ script's program lints clean (docs/analysis.md)
if ! timeout -k 10 600 env JAX_PLATFORMS=cpu \
        python -m paddle_tpu --lint-selftest \
        > /tmp/_t1_linttest.log 2>&1; then
    echo "TIER1 REGRESSION: lint selftest failed" >&2
    cat /tmp/_t1_linttest.log >&2
    exit 1
fi
# sharding/comm-contract smoke: the communication contract analyzer —
# four planted constraint-placement violations (symmetric fsdp pin,
# fsdp-composed grad carry, forbidden activation reshard, in-loop
# reduce-scatter caught by zero3_grad_contract) each caught with the
# right kind/axis/loop attribution, CommPlan mesh-axis recovery +
# comm_diff, and the clean-GPT sweep (every memory_optimize policy x
# FSDP on/off x ZeRO on/off on the 8-device CPU mesh) reporting zero
# error-severity comm findings under the attached training contracts,
# zero3_grad_contract included (docs/analysis.md "Communication
# contracts")
if ! timeout -k 10 600 env JAX_PLATFORMS=cpu \
        python -m paddle_tpu --sharding-selftest \
        > /tmp/_t1_sharding.log 2>&1; then
    echo "TIER1 REGRESSION: sharding selftest failed" >&2
    cat /tmp/_t1_sharding.log >&2
    exit 1
fi
# tracing smoke: the end-to-end tracing engine — span runtime semantics,
# the trainer's five step-phase spans into a valid Chrome-trace file,
# the serving request span tree's TTFT decomposition (queue + prefill
# within 10% of the ttft histogram), and the --bench-history gate
# exiting non-zero on a planted failed/regressed artifact fixture
# (docs/observability.md)
if ! timeout -k 10 600 env JAX_PLATFORMS=cpu \
        python -m paddle_tpu --trace-selftest \
        > /tmp/_t1_trace.log 2>&1; then
    echo "TIER1 REGRESSION: trace selftest failed" >&2
    cat /tmp/_t1_trace.log >&2
    exit 1
fi
# resilience smoke: the elastic resilience engine — a trainer subprocess
# on the 8-device virtual CPU mesh SIGKILLed mid-pass (PADDLE_TPU_FAULT)
# resumes from its latest loadable full-state checkpoint (params +
# optimizer state + RNG + reader cursor) and reproduces the
# uninterrupted loss trajectory bit-exact, and a crash injected DURING
# checkpoint publish still leaves a loadable checkpoint via the .old
# fallback (docs/resilience.md)
if ! timeout -k 10 900 env JAX_PLATFORMS=cpu \
        python -m paddle_tpu --resilience-selftest \
        > /tmp/_t1_resilience.log 2>&1; then
    echo "TIER1 REGRESSION: resilience selftest failed" >&2
    cat /tmp/_t1_resilience.log >&2
    exit 1
fi
# autotune smoke: the measured schedule search on the CPU backend — a
# toy-transformer search whose HBM preflight rejects over-budget
# candidates from compiled cost analysis alone and whose winner beats
# the worst measured candidate, a pure cache hit (zero recompiles) on
# the second invocation, PADDLE_TPU_TUNE=0 bit-exact vs untuned
# defaults, and the t=16k static prune rejecting the BENCH_r05 config
# (docs/autotune.md)
if ! timeout -k 10 600 env JAX_PLATFORMS=cpu \
        python -m paddle_tpu --tune-selftest \
        > /tmp/_t1_tune.log 2>&1; then
    echo "TIER1 REGRESSION: tune selftest failed" >&2
    cat /tmp/_t1_tune.log >&2
    exit 1
fi
# kernel-registry smoke: the multi-backend kernel subsystem — registry
# resolution + override precedence on this host, oracle parity of every
# available backend (plus interpret-forced Mosaic/triton kernels)
# against the pure-XLA reference within the documented tolerances,
# paged-attention parity over ragged block chains (trash-block masking,
# CoW forks, the fully-cached one-token prefill) with the
# PADDLE_TPU_PAGED_ATTN kill switch provably toggling the compiled
# serving spelling, PADDLE_TPU_KERNEL_BACKEND=xla_ref running the full
# GPT trainer path under every memory_optimize policy with ZERO Pallas
# calls in the jaxpr, and the interpret-mode-in-timed-run lint finding
# planted and detected (docs/kernels.md)
if ! timeout -k 10 600 env JAX_PLATFORMS=cpu \
        python -m paddle_tpu --kernels-selftest \
        > /tmp/_t1_kernels.log 2>&1; then
    echo "TIER1 REGRESSION: kernels selftest failed" >&2
    cat /tmp/_t1_kernels.log >&2
    exit 1
fi
# learned-cost-model smoke: the observability->tuning loop closed — two
# real CPU-measured toy-GPT runs seed the measurement corpus through the
# production MetricsReporter JSONL path, the fitted roofline's holdout
# error strictly improves on the analytic model's recorded error, the
# t=16k static prune under the fitted model still rejects the known-OOM
# BENCH_r05 config and selects the same known-good schedule, corrupt/
# truncated/schema-mismatched model files degrade to analytic defaults,
# and PADDLE_TPU_COSTMODEL=0 is bit-exact vs the no-model baseline
# (docs/observability.md "Cost model calibration")
if ! timeout -k 10 600 env JAX_PLATFORMS=cpu \
        python -m paddle_tpu --costmodel-selftest \
        > /tmp/_t1_costmodel.log 2>&1; then
    echo "TIER1 REGRESSION: costmodel selftest failed" >&2
    cat /tmp/_t1_costmodel.log >&2
    exit 1
fi
# attribution smoke: the per-op performance attribution engine + crash
# flight recorder — the compiled GPT flagship-family step's attribution
# table covers >= 95% of cost-analysis flops with a tune-style workload
# key, the roofline estimate-vs-measured error is reported, injected
# NaN/watchdog faults each dump a loadable flight bundle containing the
# triggering step, and a planted bench-history regression is attributed
# to the op class whose share moved (docs/observability.md)
if ! timeout -k 10 600 env JAX_PLATFORMS=cpu \
        python -m paddle_tpu --attribution-selftest \
        > /tmp/_t1_attr.log 2>&1; then
    echo "TIER1 REGRESSION: attribution selftest failed" >&2
    cat /tmp/_t1_attr.log >&2
    exit 1
fi
# speculative-decoding smoke: draft-model propose / single-pass target
# verify / token-exact rollback on the paged serving engine — a
# depth-pruned draft emits TOKEN-EXACT output vs single-stream greedy
# (f32 + bf16, prefix reuse on/off), a self-draft run's acceptance ~1
# proves the parallel verify window bit-consistent with the sequential
# step, an adversarial draft stays exact, propose/rollback leaves
# blocks_in_use at the plain engine's baseline, and PADDLE_TPU_SPEC=0
# is bit-exact with zero spec metrics (docs/serving.md)
if ! timeout -k 10 600 env JAX_PLATFORMS=cpu \
        python -m paddle_tpu --spec-selftest \
        > /tmp/_t1_spec.log 2>&1; then
    echo "TIER1 REGRESSION: spec selftest failed" >&2
    cat /tmp/_t1_spec.log >&2
    exit 1
fi
# bench-history gate: every BENCH_*/MULTICHIP_* artifact in the repo
# must classify (failures acknowledged in tools/bench_known_failures.json
# with a root cause, never silent) and no tracked metric may regress
# >10% vs best-so-far — a rotted bench artifact fails CI here instead of
# sitting on disk (the BENCH_r05 lesson)
if ! timeout -k 10 120 env JAX_PLATFORMS=cpu \
        python -m paddle_tpu --bench-history \
        > /tmp/_t1_benchhist.json 2> /tmp/_t1_benchhist.log; then
    echo "TIER1 REGRESSION: bench-history gate failed" >&2
    cat /tmp/_t1_benchhist.log >&2
    cat /tmp/_t1_benchhist.json >&2
    exit 1
fi
if ! python -c "
import json
rows = [json.loads(l) for l in open('/tmp/_t1_benchhist.json') if l.strip()]
assert len(rows) == 1, f'expected ONE json line, got {len(rows)}'
row = rows[0]
for k in ('metric', 'artifacts', 'failed', 'regressions', 'ok'):
    assert k in row, f'missing field {k}: {row}'
assert row['ok'] is True, row
print('bench history:', json.dumps(row))
"; then
    echo "TIER1 REGRESSION: bench-history emitted invalid JSON" >&2
    cat /tmp/_t1_benchhist.json >&2
    exit 1
fi
# serving smoke: the continuous-batching engine must beat the sequential
# single-stream baseline, SLO-scheduled goodput must beat the FIFO
# baseline's goodput under the same shared-prefix Poisson load, and the
# paged prefix-reuse cache must hit (prefix_hit_rate > 0, strictly fewer
# prefill tokens than reuse-off), and the speculative pass must beat the
# SLO pass's goodput on the same arrival schedule with zero scratch-block
# leak — all asserted inside --smoke — and the script must print ONE
# parseable JSON row with the throughput/latency/goodput/prefix/compile/
# speculative fields
if ! timeout -k 10 600 env JAX_PLATFORMS=cpu \
        python benchmarks/serving.py --smoke \
        > /tmp/_t1_serving.json 2> /tmp/_t1_serving.log; then
    echo "TIER1 REGRESSION: serving smoke failed" >&2
    cat /tmp/_t1_serving.log >&2
    cat /tmp/_t1_serving.json >&2
    exit 1
fi
if ! python -c "
import json, sys
rows = [json.loads(l) for l in open('/tmp/_t1_serving.json') if l.strip()]
assert len(rows) == 1, f'expected ONE json line, got {len(rows)}'
row = rows[0]
for k in ('tok_s', 'baseline_tok_s', 'speedup', 'ttft_p50_ms',
          'e2e_p99_ms', 'prefill_compiles', 'decode_compiles',
          'goodput_under_slo', 'slo_violations', 'prefix_hit_rate',
          'shed_total', 'fifo_goodput_under_slo', 'prefill_tokens',
          'fifo_prefill_tokens', 'cow_copies',
          'spec_goodput_under_slo', 'spec_accept_rate', 'spec_speedup',
          'serving_decode_hbm_bytes', 'serving_attn_bytes',
          'serving_decode_hbm_bytes_gather', 'serving_attn_bytes_gather'):
    assert k in row, f'missing field {k}: {row}'
print('serving smoke:', json.dumps(row))
"; then
    echo "TIER1 REGRESSION: serving smoke emitted invalid JSON" >&2
    cat /tmp/_t1_serving.json >&2
    exit 1
fi
exit $rc
